// dcnxferd — per-node DCN transfer daemon (native C++).
//
// TPU-native analog of the reference's tcpgpudmarxd RX-datapath manager
// (SURVEY.md §2.2; ref: gpudirect-tcpx/nccl-test.yaml:29-52 runs it as a
// privileged sidecar owning flow-steering state and GPU-memory RX buffers,
// with a UDS control socket under /run/tcpx).  Here the daemon owns the
// node's cross-slice DCN transfer state: workers register flows, the daemon
// allocates pinned staging buffers from a bounded pool (mmap'd, mlock
// best-effort), accounts transferred bytes, and releases a client's flows
// when its connection drops — the same client-lifetime contract rxdm gives
// the NCCL plugin.
//
// Control protocol: newline-delimited JSON over a UNIX stream socket
// (<uds_path>/xferd.sock).  Requests are flat objects:
//   {"op":"version"}
//   {"op":"register_flow","flow":"g0","peer":"slice1-h0","bytes":4194304}
//   {"op":"record_transfer","flow":"g0","bytes":1048576}
//   {"op":"release_flow","flow":"g0"}
//   {"op":"data_port"}
//   {"op":"send","host":"10.0.0.2","port":"7474","flow":"g0","bytes":N}
//   {"op":"read","flow":"g0","bytes":N,"offset":M}   (base64 payload out)
//   {"op":"stats"}
// Responses: {"ok":true,...} or {"ok":false,"error":"..."}.
//
// Data plane: a TCP listener (--data_port, 0 = ephemeral) receives
// framed transfers from peer daemons into the registered flow's staging
// buffer — the in-repo stand-in for the devmem-TCP RX datapath rxdm
// programs on GPUs; over real DCN the frames ride the inter-pod fabric.
// Frame: "DXF1" magic, u32 LE flow-name length, u64 LE payload length,
// then the name and payload.  The "send" control op streams a flow's
// staging buffer to a peer daemon and reports achieved throughput.
//
// Build: make native  (g++ -std=c++17, no external deps).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

int g_verbose = 0;
volatile sig_atomic_t g_stop = 0;

void logf(int level, const char* fmt, ...) {
  if (level > g_verbose) return;
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "dcnxferd: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
}

void on_signal(int) { g_stop = 1; }

// ---- minimal flat-JSON request parsing -------------------------------------
// Requests are single-level objects with string or integer values; anything
// else is a protocol error.  (Responses are emitted with snprintf.)

bool ParseFlatJson(const std::string& line,
                   std::map<std::string, std::string>* out) {
  size_t i = 0;
  auto skip_ws = [&] {
    while (i < line.size() && isspace((unsigned char)line[i])) i++;
  };
  auto parse_string = [&](std::string* s) -> bool {
    if (line[i] != '"') return false;
    i++;
    s->clear();
    while (i < line.size() && line[i] != '"') {
      if (line[i] == '\\' && i + 1 < line.size()) i++;  // unescape next
      s->push_back(line[i++]);
    }
    if (i >= line.size()) return false;
    i++;  // closing quote
    return true;
  };
  skip_ws();
  if (i >= line.size() || line[i] != '{') return false;
  i++;
  skip_ws();
  if (i < line.size() && line[i] == '}') return true;  // empty object
  while (i < line.size()) {
    skip_ws();
    std::string key, value;
    if (!parse_string(&key)) return false;
    skip_ws();
    if (i >= line.size() || line[i] != ':') return false;
    i++;
    skip_ws();
    if (i >= line.size()) return false;
    if (line[i] == '"') {
      if (!parse_string(&value)) return false;
    } else {  // bare token: number / true / false / null
      size_t start = i;
      while (i < line.size() && line[i] != ',' && line[i] != '}' &&
             !isspace((unsigned char)line[i]))
        i++;
      value = line.substr(start, i - start);
    }
    (*out)[key] = value;
    skip_ws();
    if (i < line.size() && line[i] == ',') {
      i++;
      continue;
    }
    if (i < line.size() && line[i] == '}') return true;
    return false;
  }
  return false;
}

// Flow and peer names are operator/workload-supplied; constraining them
// keeps every response JSON well-formed without an escaper and bounds the
// fixed-size response buffers.
constexpr size_t kMaxNameLen = 64;
bool IsValidName(const std::string& s) {
  if (s.empty() || s.size() > kMaxNameLen) return false;
  for (char ch : s) {
    if (!isalnum((unsigned char)ch) && ch != '-' && ch != '_' && ch != '.' &&
        ch != ':' && ch != '/')
      return false;
  }
  return true;
}

std::string JsonEscape(const std::string& s) {
  std::string out;
  for (char ch : s) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(ch);
    } else if ((unsigned char)ch < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", ch);
      out += buf;
    } else {
      out.push_back(ch);
    }
  }
  return out;
}

// ---- flow / buffer-pool state ----------------------------------------------

struct Flow {
  std::string name;
  std::string peer;
  int owner_fd = -1;
  size_t buffer_bytes = 0;
  void* buffer = nullptr;
  unsigned long long transferred = 0;  // bytes sent / recorded by owner
  unsigned long long rx_bytes = 0;     // bytes landed via the data plane
  // Bytes of the most recent COMPLETED frame that landed in the staging
  // buffer (clamped to buffer_bytes).  Reads are bounded by this, not
  // by buffer_bytes: before any frame lands the buffer holds zeros, and
  // after a shorter second frame the previous frame's tail is stale —
  // neither must be readable as if it were payload.
  unsigned long long frame_bytes = 0;
};

// Data-plane frame header: magic + flow-name length + payload length.
constexpr char kFrameMagic[4] = {'D', 'X', 'F', '1'};
constexpr size_t kFrameHdrLen = 16;  // 4 magic + 4 name_len + 8 payload_len

unsigned long long NowMicros() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (unsigned long long)ts.tv_sec * 1000000ull + ts.tv_nsec / 1000;
}

class Daemon {
 public:
  Daemon(size_t pool_bytes, size_t max_flows)
      : pool_bytes_(pool_bytes), max_flows_(max_flows) {}

  std::string Handle(int fd, const std::map<std::string, std::string>& req) {
    auto it = req.find("op");
    if (it == req.end()) return Err("missing op");
    const std::string& op = it->second;
    if (op == "version") return Ok("\"version\":\"dcnxferd/1.2\"");
    if (op == "ping") return Ok("");
    if (op == "register_flow") return RegisterFlow(fd, req);
    if (op == "record_transfer") return RecordTransfer(fd, req);
    if (op == "release_flow") return ReleaseFlow(fd, req);
    if (op == "data_port") return DataPort();
    if (op == "send") return Send(fd, req);
    if (op == "read") return Read(fd, req);
    if (op == "stats") return Stats();
    return Err("unknown op '" + op + "'");
  }

  void set_data_port(int port) { data_port_ = port; }

  // A data-plane frame finished: remember how much of it actually
  // landed in the staging buffer so reads can be clamped to real data.
  void RecordFrameComplete(const std::string& flow,
                           unsigned long long frame_len) {
    auto it = flows_.find(flow);
    if (it == flows_.end()) return;
    unsigned long long landed = frame_len;
    if (landed > it->second.buffer_bytes)
      landed = it->second.buffer_bytes;
    it->second.frame_bytes = landed;
  }

  // Data-plane landing: account a received chunk against its flow (or
  // the unmatched counter when no local flow has that name).
  void RecordRx(const std::string& flow, size_t n) {
    total_rx_ += n;
    auto it = flows_.find(flow);
    if (it != flows_.end()) {
      it->second.rx_bytes += n;
    } else {
      rx_unmatched_ += n;
    }
  }

  // Staging buffer a data connection lands payloads into; null when the
  // flow is unknown (payload is then drained and only counted).
  char* RxBuffer(const std::string& flow, size_t* cap) {
    auto it = flows_.find(flow);
    if (it == flows_.end()) return nullptr;
    *cap = it->second.buffer_bytes;
    return (char*)it->second.buffer;
  }

  void ReleaseClient(int fd) {
    for (auto it = flows_.begin(); it != flows_.end();) {
      if (it->second.owner_fd == fd) {
        logf(1, "releasing flow '%s' (client fd %d gone)",
             it->first.c_str(), fd);
        FreeFlow(&it->second);
        it = flows_.erase(it);
      } else {
        ++it;
      }
    }
  }

  ~Daemon() {
    for (auto& kv : flows_) FreeFlow(&kv.second);
  }

 private:
  static std::string Ok(const std::string& extra) {
    return extra.empty() ? "{\"ok\":true}"
                         : "{\"ok\":true," + extra + "}";
  }
  static std::string Err(const std::string& msg) {
    return "{\"ok\":false,\"error\":\"" + msg + "\"}";
  }

  std::string RegisterFlow(int fd,
                           const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end() || fit->second.empty())
      return Err("register_flow needs 'flow'");
    const std::string& name = fit->second;
    if (!IsValidName(name))
      return Err("invalid flow name (max 64 chars of [A-Za-z0-9._:/-])");
    if (flows_.count(name))
      return Err("flow '" + JsonEscape(name) + "' already exists");
    if (flows_.size() >= max_flows_) return Err("max flows reached");

    size_t bytes = 4 << 20;  // default 4 MiB staging buffer
    auto bit = req.find("bytes");
    if (bit != req.end()) {
      if (bit->second.empty() || !isdigit((unsigned char)bit->second[0]))
        return Err("invalid 'bytes'");
      char* end = nullptr;
      unsigned long long v = strtoull(bit->second.c_str(), &end, 10);
      if (end == bit->second.c_str() || *end != '\0' || v == 0 ||
          v > (1ull << 40))
        return Err("invalid 'bytes'");
      bytes = (size_t)v;
    }
    // Page-align; enforce the pool bound.
    size_t page = (size_t)sysconf(_SC_PAGESIZE);
    bytes = (bytes + page - 1) / page * page;
    if (pool_used_ + bytes > pool_bytes_)
      return Err("buffer pool exhausted");

    void* buf = mmap(nullptr, bytes, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (buf == MAP_FAILED) return Err("mmap failed");
    // Pin best-effort: staging buffers should not page out mid-transfer.
    // Unprivileged runs (tests) may exceed RLIMIT_MEMLOCK; that is fine.
    if (mlock(buf, bytes) != 0)
      logf(2, "mlock(%zu) failed: %s (continuing unpinned)", bytes,
           strerror(errno));

    Flow f;
    f.name = name;
    auto pit = req.find("peer");
    if (pit != req.end()) {
      if (!pit->second.empty() && !IsValidName(pit->second))
        return Err("invalid peer name (max 64 chars of [A-Za-z0-9._:/-])");
      f.peer = pit->second;
    }
    f.owner_fd = fd;
    f.buffer_bytes = bytes;
    f.buffer = buf;
    pool_used_ += bytes;
    flows_[name] = f;
    logf(1, "registered flow '%s' peer='%s' buffer=%zu", name.c_str(),
         f.peer.c_str(), bytes);

    char extra[160];
    snprintf(extra, sizeof(extra),
             "\"flow\":\"%s\",\"buffer_bytes\":%zu,\"pool_used\":%zu",
             name.c_str(), bytes, pool_used_);
    return Ok(extra);
  }

  std::string RecordTransfer(int fd,
                             const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("record_transfer needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");
    auto bit = req.find("bytes");
    if (bit == req.end()) return Err("record_transfer needs 'bytes'");
    // Reject signs and garbage; strtoull would silently wrap "-1" to 2^64-1.
    if (bit->second.empty() || !isdigit((unsigned char)bit->second[0]))
      return Err("invalid 'bytes'");
    char* end = nullptr;
    unsigned long long v = strtoull(bit->second.c_str(), &end, 10);
    if (end == bit->second.c_str() || *end != '\0' || v > (1ull << 62))
      return Err("invalid 'bytes'");
    it->second.transferred += v;
    total_transferred_ += v;
    char extra[96];
    snprintf(extra, sizeof(extra), "\"flow_bytes\":%llu",
             it->second.transferred);
    return Ok(extra);
  }

  std::string ReleaseFlow(int fd,
                          const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("release_flow needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");
    FreeFlow(&it->second);
    flows_.erase(it);
    return Ok("");
  }

  std::string DataPort() {
    if (data_port_ < 0) return Err("data plane disabled");
    char extra[48];
    snprintf(extra, sizeof(extra), "\"port\":%d", data_port_);
    return Ok(extra);
  }

  // Stream a flow's staging buffer to a peer daemon's data port.  This
  // blocks the control loop for the duration of the transfer (bounded by
  // SO_SNDTIMEO); benchmark-issued sends are the expected caller, matching
  // the reference rig where nccl-tests drives the datapath directly.
  std::string Send(int fd, const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("send needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");
    auto hit = req.find("host");
    if (hit == req.end() || hit->second.empty())
      return Err("send needs 'host'");
    auto pit = req.find("port");
    if (pit == req.end()) return Err("send needs 'port'");
    int port = atoi(pit->second.c_str());
    if (port <= 0 || port > 65535) return Err("invalid 'port'");

    unsigned long long nbytes = it->second.buffer_bytes;
    auto bit = req.find("bytes");
    if (bit != req.end()) {
      if (bit->second.empty() || !isdigit((unsigned char)bit->second[0]))
        return Err("invalid 'bytes'");
      char* end = nullptr;
      nbytes = strtoull(bit->second.c_str(), &end, 10);
      if (end == bit->second.c_str() || *end != '\0' || nbytes == 0 ||
          nbytes > (1ull << 40))
        return Err("invalid 'bytes'");
    }

    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, hit->second.c_str(), &addr.sin_addr) != 1)
      return Err("invalid 'host' (IPv4 literal required)");

    int sfd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (sfd < 0) return Err(std::string("socket: ") + strerror(errno));
    timeval tv{30, 0};
    setsockopt(sfd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    setsockopt(sfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(sfd, (sockaddr*)&addr, sizeof(addr)) != 0) {
      std::string e = strerror(errno);
      close(sfd);
      return Err("connect: " + e);
    }

    // Frame header.
    const std::string& name = it->second.name;
    char hdr[kFrameHdrLen];
    memcpy(hdr, kFrameMagic, 4);
    uint32_t nl = (uint32_t)name.size();
    uint64_t pl = nbytes;
    memcpy(hdr + 4, &nl, 4);
    memcpy(hdr + 8, &pl, 8);
    unsigned long long t0 = NowMicros();
    bool okay = WriteAll(sfd, hdr, sizeof(hdr)) &&
                WriteAll(sfd, name.data(), name.size());
    // Payload: the staging buffer, repeated to cover nbytes.
    unsigned long long left = nbytes;
    const char* buf = (const char*)it->second.buffer;
    size_t cap = it->second.buffer_bytes;
    while (okay && left > 0) {
      size_t chunk = (size_t)(left < cap ? left : cap);
      okay = WriteAll(sfd, buf, chunk);
      left -= chunk;
    }
    close(sfd);
    if (!okay) return Err("send failed mid-stream");
    unsigned long long micros = NowMicros() - t0;
    if (micros == 0) micros = 1;
    it->second.transferred += nbytes;
    total_transferred_ += nbytes;
    double gbps = (double)nbytes / 1e9 / ((double)micros / 1e6);
    char extra[160];
    snprintf(extra, sizeof(extra),
             "\"bytes\":%llu,\"micros\":%llu,\"gbps\":%.3f", nbytes, micros,
             gbps);
    return Ok(extra);
  }

  // Read back staged bytes, base64 over the control socket.  This is the
  // consumer-side seam the in-repo datapath needs to be end-to-end: a
  // worker process reads the payload a PEER daemon landed into its flow
  // (tests/test_dcn_jax_integration.py drives this from jax.distributed
  // workers).  Bounded to 512 KiB per call so the base64 response fits
  // kMaxOutbuf; the client chunks larger reads by offset.
  std::string Read(int fd, const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("read needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");

    unsigned long long offset = 0, nbytes = it->second.buffer_bytes;
    auto oit = req.find("offset");
    if (oit != req.end()) {
      if (!ParseU64(oit->second, &offset)) return Err("invalid 'offset'");
    }
    auto bit = req.find("bytes");
    if (bit != req.end()) {
      if (!ParseU64(bit->second, &nbytes) || nbytes == 0)
        return Err("invalid 'bytes'");
    }
    if (offset >= it->second.buffer_bytes)
      return Err("'offset' beyond staging buffer");
    // Clamp to the last COMPLETED frame, not the buffer: before any
    // frame lands the buffer is zeros, and after a shorter frame the
    // previous frame's tail is stale — returning either as payload
    // gives callers torn data with an ok=true response (ADVICE r03).
    unsigned long long staged = it->second.frame_bytes;
    if (staged == 0)
      return Err("no completed frame staged in flow '" +
                 JsonEscape(fit->second) + "'");
    if (offset >= staged)
      return Err("'offset' beyond staged data (frame_bytes=" +
                 std::to_string(staged) + ")");
    if (nbytes > staged - offset) nbytes = staged - offset;
    if (nbytes > (512ull << 10))
      return Err("read capped at 512 KiB per call");

    std::string b64 =
        Base64((const unsigned char*)it->second.buffer + offset,
               (size_t)nbytes);
    std::string extra = "\"bytes\":" + std::to_string(nbytes) +
                        ",\"frame_bytes\":" + std::to_string(staged) +
                        ",\"data\":\"" + b64 + "\"";
    return Ok(extra);
  }

  std::string Stats() {
    std::string detail = "[";
    bool first = true;
    for (const auto& kv : flows_) {
      char item[448];  // names are <=64 chars (IsValidName), so this fits
      snprintf(item, sizeof(item),
               "%s{\"flow\":\"%s\",\"peer\":\"%s\",\"buffer_bytes\":%zu,"
               "\"transferred\":%llu,\"rx_bytes\":%llu,"
               "\"frame_bytes\":%llu}",
               first ? "" : ",", kv.second.name.c_str(),
               kv.second.peer.c_str(), kv.second.buffer_bytes,
               kv.second.transferred, kv.second.rx_bytes,
               kv.second.frame_bytes);
      detail += item;
      first = false;
    }
    detail += "]";
    char extra[320];
    snprintf(extra, sizeof(extra),
             "\"pool_bytes\":%zu,\"pool_used\":%zu,\"active_flows\":%zu,"
             "\"total_transferred\":%llu,\"total_rx\":%llu,"
             "\"rx_unmatched\":%llu,\"flows\":",
             pool_bytes_, pool_used_, flows_.size(), total_transferred_,
             total_rx_, rx_unmatched_);
    return Ok(extra + detail);
  }

  // Strict unsigned parse: digits only, bounded well below wrap range.
  static bool ParseU64(const std::string& s, unsigned long long* out) {
    if (s.empty() || !isdigit((unsigned char)s[0])) return false;
    char* end = nullptr;
    unsigned long long v = strtoull(s.c_str(), &end, 10);
    if (end == s.c_str() || *end != '\0' || v > (1ull << 62)) return false;
    *out = v;
    return true;
  }

  static std::string Base64(const unsigned char* data, size_t n) {
    static const char tbl[] =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    std::string out;
    out.reserve((n + 2) / 3 * 4);
    size_t i = 0;
    for (; i + 3 <= n; i += 3) {
      unsigned v = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2];
      out.push_back(tbl[(v >> 18) & 63]);
      out.push_back(tbl[(v >> 12) & 63]);
      out.push_back(tbl[(v >> 6) & 63]);
      out.push_back(tbl[v & 63]);
    }
    if (i < n) {
      unsigned v = data[i] << 16;
      if (i + 1 < n) v |= data[i + 1] << 8;
      out.push_back(tbl[(v >> 18) & 63]);
      out.push_back(tbl[(v >> 12) & 63]);
      out.push_back(i + 1 < n ? tbl[(v >> 6) & 63] : '=');
      out.push_back('=');
    }
    return out;
  }

  static bool WriteAll(int fd, const void* data, size_t n) {
    const char* p = (const char*)data;
    while (n > 0) {
      ssize_t put = write(fd, p, n);
      if (put < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      if (put == 0) return false;
      p += put;
      n -= (size_t)put;
    }
    return true;
  }

  void FreeFlow(Flow* f) {
    if (f->buffer) {
      munlock(f->buffer, f->buffer_bytes);
      munmap(f->buffer, f->buffer_bytes);
      f->buffer = nullptr;
    }
    pool_used_ -= f->buffer_bytes;
  }

  size_t pool_bytes_;
  size_t max_flows_;
  size_t pool_used_ = 0;
  int data_port_ = -1;
  unsigned long long total_transferred_ = 0;
  unsigned long long total_rx_ = 0;
  unsigned long long rx_unmatched_ = 0;
  std::map<std::string, Flow> flows_;
};

// ---- event loop ------------------------------------------------------------

struct Client {
  int fd;
  std::string inbuf;
  std::string outbuf;  // pending response bytes (client slow to read)
};

// A client that won't drain 1 MiB of pending responses is broken or
// malicious; drop it rather than buffer without bound.
constexpr size_t kMaxOutbuf = 1 << 20;
constexpr size_t kMaxInbuf = 1 << 16;

// Returns false when the connection is dead.  Writes what it can now and
// leaves the rest in outbuf for POLLOUT — one stuck client must never
// block the event loop (fds are non-blocking).
bool FlushClient(Client* c) {
  while (!c->outbuf.empty()) {
    ssize_t put = write(c->fd, c->outbuf.data(), c->outbuf.size());
    if (put > 0) {
      c->outbuf.erase(0, (size_t)put);
    } else if (put < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return true;  // try again on POLLOUT
    } else {
      return false;
    }
  }
  return true;
}

// A peer-daemon data connection, advanced incrementally by the poll
// loop: header -> flow name -> payload (landed into the flow's staging
// buffer), then back to header for the next frame.
struct DataConn {
  int fd;
  enum { HDR, NAME, PAYLOAD } state = HDR;
  std::string acc;                 // header/name accumulator
  uint32_t name_len = 0;
  unsigned long long remaining = 0;
  unsigned long long frame_len = 0;  // total payload bytes this frame
  std::string flow;
  unsigned long long t0 = 0;       // frame start (throughput log)
};

// Advance one data connection; returns false when it should be closed.
bool PumpDataConn(DataConn* dc, Daemon* daemon) {
  char tmp[64 << 10];
  for (;;) {
    if (dc->state == DataConn::PAYLOAD) {
      size_t cap = 0;
      char* flow_buf = daemon->RxBuffer(dc->flow, &cap);
      size_t want = sizeof(tmp);
      char* dst = tmp;
      // Land at the frame's running offset so multi-chunk payloads
      // append instead of overwriting offset 0; bytes beyond the
      // staging buffer are drained and only counted.
      unsigned long long landed = dc->frame_len - dc->remaining;
      if (flow_buf && landed < (unsigned long long)cap) {
        dst = flow_buf + landed;
        want = cap - (size_t)landed;
      }
      if ((unsigned long long)want > dc->remaining)
        want = (size_t)dc->remaining;
      ssize_t got = read(dc->fd, dst, want);
      if (got < 0)
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      if (got == 0) return false;
      daemon->RecordRx(dc->flow, (size_t)got);
      dc->remaining -= (unsigned long long)got;
      if (dc->remaining == 0) {
        unsigned long long micros = NowMicros() - dc->t0;
        logf(1, "frame complete: flow '%s' in %llu us", dc->flow.c_str(),
             micros ? micros : 1);
        daemon->RecordFrameComplete(dc->flow, dc->frame_len);
        dc->state = DataConn::HDR;
        dc->acc.clear();
      }
      continue;
    }
    // Header / name bytes.
    size_t need = (dc->state == DataConn::HDR)
                      ? kFrameHdrLen - dc->acc.size()
                      : dc->name_len - dc->acc.size();
    if (need == 0 && dc->state == DataConn::NAME) {
      dc->flow = dc->acc;
      dc->acc.clear();
      dc->state = DataConn::PAYLOAD;
      continue;
    }
    ssize_t got = read(dc->fd, tmp, need < sizeof(tmp) ? need : sizeof(tmp));
    if (got < 0)
      return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
    if (got == 0) return false;
    dc->acc.append(tmp, (size_t)got);
    if (dc->state == DataConn::HDR && dc->acc.size() == kFrameHdrLen) {
      if (memcmp(dc->acc.data(), kFrameMagic, 4) != 0) {
        logf(0, "data conn fd %d: bad frame magic", dc->fd);
        return false;
      }
      memcpy(&dc->name_len, dc->acc.data() + 4, 4);
      memcpy(&dc->remaining, dc->acc.data() + 8, 8);
      dc->frame_len = dc->remaining;
      if (dc->name_len == 0 || dc->name_len > kMaxNameLen ||
          dc->remaining > (1ull << 40)) {
        logf(0, "data conn fd %d: bad frame header", dc->fd);
        return false;
      }
      dc->acc.clear();
      dc->state = DataConn::NAME;
      dc->t0 = NowMicros();
    }
  }
}

int MakeTcpListener(int port, int* bound_port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    perror("tcp socket");
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    perror("tcp bind/listen");
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  if (getsockname(fd, (sockaddr*)&addr, &alen) == 0)
    *bound_port = ntohs(addr.sin_port);
  return fd;
}

int MakeListener(const std::string& sock_path) {
  int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    perror("socket");
    return -1;
  }
  // Bind under a temp name and rename() into place only after listen():
  // clients (and the readiness checks in the install DS and tests) treat
  // the socket file's existence as "accepting connections", so the path
  // must never be visible in the bound-but-not-listening window.
  const std::string tmp_path = sock_path + ".tmp";
  unlink(sock_path.c_str());
  unlink(tmp_path.c_str());
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (tmp_path.size() >= sizeof(addr.sun_path)) {
    fprintf(stderr, "dcnxferd: socket path too long (with .tmp suffix): %s\n",
            tmp_path.c_str());
    close(fd);
    return -1;
  }
  strncpy(addr.sun_path, tmp_path.c_str(), sizeof(addr.sun_path) - 1);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    close(fd);
    return -1;
  }
  chmod(tmp_path.c_str(), 0666);  // workload pods connect unprivileged
  if (listen(fd, 64) != 0) {
    perror("listen");
    unlink(tmp_path.c_str());
    close(fd);
    return -1;
  }
  if (rename(tmp_path.c_str(), sock_path.c_str()) != 0) {
    perror("rename");
    unlink(tmp_path.c_str());
    close(fd);
    return -1;
  }
  return fd;
}

int Serve(const std::string& sock_path, Daemon* daemon, int data_port) {
  int listener = MakeListener(sock_path);
  if (listener < 0) return 1;
  logf(0, "listening on %s", sock_path.c_str());

  int tcp_listener = -1;
  if (data_port >= 0) {
    int bound = -1;
    tcp_listener = MakeTcpListener(data_port, &bound);
    if (tcp_listener < 0) return 1;
    daemon->set_data_port(bound);
    logf(0, "data plane listening on tcp :%d", bound);
  }

  std::vector<Client> clients;
  std::vector<DataConn> dconns;
  while (!g_stop) {
    std::vector<pollfd> fds;
    fds.push_back({listener, POLLIN, 0});
    for (const auto& c : clients) {
      short events = POLLIN;
      if (!c.outbuf.empty()) events |= POLLOUT;
      fds.push_back({c.fd, events, 0});
    }
    // Data-plane fds trail the control fds; their revents are handled
    // after the control clients below.
    size_t data_base = fds.size();
    if (tcp_listener >= 0) fds.push_back({tcp_listener, POLLIN, 0});
    for (const auto& dc : dconns) fds.push_back({dc.fd, POLLIN, 0});
    int n = poll(fds.data(), fds.size(), 500);
    if (n < 0) {
      if (errno == EINTR) continue;
      perror("poll");
      break;
    }
    // Data plane first: its pollfd indices are invalidated by the
    // control-client erase logic below.
    if (tcp_listener >= 0) {
      if (fds[data_base].revents & POLLIN) {
        int dfd = accept4(tcp_listener, nullptr, nullptr,
                          SOCK_CLOEXEC | SOCK_NONBLOCK);
        if (dfd >= 0) {
          DataConn dc;
          dc.fd = dfd;
          dconns.push_back(dc);
          logf(1, "data conn fd %d connected", dfd);
        }
      }
      size_t dpolled = fds.size() - (data_base + 1);
      for (size_t di = 0; di < dpolled;) {
        pollfd& p = fds[data_base + 1 + di];
        bool drop = false;
        if (p.revents & (POLLIN | POLLHUP | POLLERR)) {
          if (!PumpDataConn(&dconns[di], daemon)) drop = true;
        }
        if (drop) {
          logf(1, "data conn fd %d closed", dconns[di].fd);
          close(dconns[di].fd);
          dconns.erase(dconns.begin() + di);
          fds.erase(fds.begin() + data_base + 1 + di);
          dpolled--;
        } else {
          ++di;
        }
      }
    }
    // Only the clients present when poll() ran have valid revents; a
    // freshly-accepted client is picked up on the next loop iteration.
    size_t polled = data_base - 1;
    for (size_t ci = 0; ci < polled;) {
      Client& c = clients[ci];
      pollfd& p = fds[1 + ci];
      bool drop = false;
      if (p.revents & POLLOUT) {
        if (!FlushClient(&c)) drop = true;
      }
      if (!drop && (p.revents & (POLLIN | POLLHUP | POLLERR))) {
        char buf[4096];
        ssize_t got = read(c.fd, buf, sizeof(buf));
        if (got == 0 || (got < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          drop = true;
        } else if (got > 0) {
          c.inbuf.append(buf, (size_t)got);
          size_t nl;
          while ((nl = c.inbuf.find('\n')) != std::string::npos) {
            std::string line = c.inbuf.substr(0, nl);
            c.inbuf.erase(0, nl + 1);
            if (line.empty()) continue;
            std::map<std::string, std::string> req;
            std::string resp = ParseFlatJson(line, &req)
                                   ? daemon->Handle(c.fd, req)
                                   : "{\"ok\":false,\"error\":\"bad json\"}";
            c.outbuf += resp + "\n";
          }
          // Input lines are bounded; a client streaming garbage without
          // newlines (or not draining responses) must not grow buffers
          // forever.
          if (c.inbuf.size() > kMaxInbuf || c.outbuf.size() > kMaxOutbuf)
            drop = true;
          if (!drop && !FlushClient(&c)) drop = true;
        }
      }
      if (drop) {
        daemon->ReleaseClient(c.fd);
        close(c.fd);
        logf(1, "client fd %d disconnected", c.fd);
        clients.erase(clients.begin() + ci);
        fds.erase(fds.begin() + 1 + ci);
        polled--;
      } else {
        ++ci;
      }
    }
    if (fds[0].revents & POLLIN) {
      int cfd = accept4(listener, nullptr, nullptr,
                        SOCK_CLOEXEC | SOCK_NONBLOCK);
      if (cfd >= 0) {
        clients.push_back({cfd, "", ""});
        logf(1, "client fd %d connected", cfd);
      }
    }
  }
  for (auto& c : clients) {
    daemon->ReleaseClient(c.fd);
    close(c.fd);
  }
  for (auto& dc : dconns) close(dc.fd);
  if (tcp_listener >= 0) close(tcp_listener);
  close(listener);
  unlink(sock_path.c_str());
  logf(0, "shut down");
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string uds_path = "/run/tpu-dcn";
  size_t pool_bytes = 256ull << 20;
  size_t max_flows = 256;
  int data_port = 0;  // 0 = ephemeral; -1 disables the data plane

  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    auto next = [&]() -> const char* {
      return (i + 1 < argc) ? argv[++i] : nullptr;
    };
    if (arg == "--uds_path" || arg == "--uds-path") {
      const char* v = next();
      if (v) uds_path = v;
    } else if (arg == "--pool_bytes" || arg == "--pool-bytes") {
      const char* v = next();
      if (v) pool_bytes = strtoull(v, nullptr, 10);
    } else if (arg == "--max_flows" || arg == "--max-flows") {
      const char* v = next();
      if (v) max_flows = strtoull(v, nullptr, 10);
    } else if (arg == "--data_port" || arg == "--data-port") {
      const char* v = next();
      if (v) data_port = atoi(v);
    } else if (arg == "--verbose" || arg == "-v") {
      const char* v = next();
      if (v) g_verbose = atoi(v);
    } else if (arg == "--help" || arg == "-h") {
      printf("usage: dcnxferd [--uds_path DIR] [--pool_bytes N] "
             "[--max_flows N] [--data_port P|-1] [--verbose LEVEL]\n");
      return 0;
    } else {
      fprintf(stderr, "dcnxferd: unknown flag %s\n", arg.c_str());
      return 2;
    }
  }

  mkdir(uds_path.c_str(), 0755);
  signal(SIGINT, on_signal);
  signal(SIGTERM, on_signal);
  signal(SIGPIPE, SIG_IGN);

  Daemon daemon(pool_bytes, max_flows);
  return Serve(uds_path + "/xferd.sock", &daemon, data_port);
}
