// tokpack — pack pre-tokenized corpora into the framework's token-shard
// format (data/tokens.py): NNNNN.tokens files of little-endian uint32
// plus an index.json.
//
// The reference consumed its input pipeline as a vendor C++ runtime
// (tf.data inside the demo trainer images, demo/gpu-training/
// generate_job.sh:54-70); this is the in-tree native piece of ours:
// the hot loop — parsing gigabytes of decimal token ids and streaming
// them into shards — runs in C++, while the training-side reader stays
// a ~100-line memory-mapped Python module.
//
// Usage:
//   tokpack --out DIR [--shard-tokens N] FILE...   (or - for stdin)
//
// Input: whitespace-separated decimal token ids (any mix of spaces /
// newlines).  Output shards hold exactly --shard-tokens tokens except
// the last.  Exit codes: 0 ok, 1 usage, 2 I/O or parse error.

#include <dirent.h>
#include <sys/stat.h>

#include <cerrno>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr size_t kBufBytes = 1 << 20;

struct ShardWriter {
  std::string dir;
  uint64_t shard_tokens;
  std::vector<uint64_t> counts;  // tokens per finished shard
  FILE* cur = nullptr;
  uint64_t cur_count = 0;
  std::vector<uint32_t> buf;

  explicit ShardWriter(std::string d, uint64_t per_shard)
      : dir(std::move(d)), shard_tokens(per_shard) {
    buf.reserve(kBufBytes / sizeof(uint32_t));
  }

  std::string shard_path(size_t i, bool tmp) const {
    char name[32];
    snprintf(name, sizeof(name), "%05zu.tokens", i);
    return dir + "/" + name + (tmp ? ".tmp" : "");
  }

  bool flush_buf() {
    if (buf.empty()) return true;
    size_t n = fwrite(buf.data(), sizeof(uint32_t), buf.size(), cur);
    if (n != buf.size()) {
      fprintf(stderr, "tokpack: write failed: %s\n", strerror(errno));
      return false;
    }
    buf.clear();
    return true;
  }

  bool add(uint32_t tok) {
    if (cur == nullptr) {
      std::string path = shard_path(counts.size(), /*tmp=*/true);
      cur = fopen(path.c_str(), "wb");
      if (cur == nullptr) {
        fprintf(stderr, "tokpack: %s: %s\n", path.c_str(),
                strerror(errno));
        return false;
      }
      cur_count = 0;
    }
    buf.push_back(tok);  // uint32 little-endian on every target we build
    cur_count++;
    if (buf.size() * sizeof(uint32_t) >= kBufBytes && !flush_buf())
      return false;
    if (cur_count >= shard_tokens) return close_shard();
    return true;
  }

  bool close_shard() {
    if (cur == nullptr) return true;
    if (!flush_buf()) return false;
    if (fclose(cur) != 0) {
      fprintf(stderr, "tokpack: close failed: %s\n", strerror(errno));
      return false;
    }
    cur = nullptr;
    // Publish atomically: the reader never sees a half-written shard.
    std::string tmp = shard_path(counts.size(), true);
    std::string fin = shard_path(counts.size(), false);
    if (rename(tmp.c_str(), fin.c_str()) != 0) {
      fprintf(stderr, "tokpack: rename %s: %s\n", tmp.c_str(),
              strerror(errno));
      return false;
    }
    counts.push_back(cur_count);
    cur_count = 0;
    return true;
  }

  bool write_index() {
    std::string tmp = dir + "/index.json.tmp";
    FILE* f = fopen(tmp.c_str(), "w");
    if (f == nullptr) {
      fprintf(stderr, "tokpack: %s: %s\n", tmp.c_str(), strerror(errno));
      return false;
    }
    fprintf(f, "{\n \"version\": 1,\n \"shards\": [\n");
    for (size_t i = 0; i < counts.size(); i++) {
      char name[32];
      snprintf(name, sizeof(name), "%05zu.tokens", i);
      fprintf(f, "  {\"name\": \"%s\", \"tokens\": %" PRIu64 "}%s\n",
              name, counts[i], i + 1 < counts.size() ? "," : "");
    }
    fprintf(f, " ]\n}\n");
    if (fclose(f) != 0) return false;
    std::string fin = dir + "/index.json";
    return rename(tmp.c_str(), fin.c_str()) == 0;
  }
};

bool pack_stream(FILE* in, const char* label, ShardWriter* out) {
  // Hand-rolled decimal scanner: the whole job is this loop, and
  // fscanf is ~5x slower on multi-GB corpora.
  std::vector<char> chunk(kBufBytes);
  uint64_t value = 0;
  bool in_number = false;
  for (;;) {
    size_t n = fread(chunk.data(), 1, chunk.size(), in);
    if (n == 0) {
      if (ferror(in)) {
        fprintf(stderr, "tokpack: %s: read error\n", label);
        return false;
      }
      break;
    }
    for (size_t i = 0; i < n; i++) {
      char c = chunk[i];
      if (c >= '0' && c <= '9') {
        value = value * 10 + static_cast<uint64_t>(c - '0');
        if (value > UINT32_MAX) {
          fprintf(stderr, "tokpack: %s: token id overflows uint32\n",
                  label);
          return false;
        }
        in_number = true;
      } else if (c == ' ' || c == '\n' || c == '\t' || c == '\r') {
        if (in_number && !out->add(static_cast<uint32_t>(value)))
          return false;
        value = 0;
        in_number = false;
      } else {
        fprintf(stderr, "tokpack: %s: unexpected byte 0x%02x (want "
                "decimal ids + whitespace)\n", label,
                static_cast<unsigned char>(c));
        return false;
      }
    }
  }
  if (in_number && !out->add(static_cast<uint32_t>(value)))
    return false;
  return true;
}

// mkdir -p: create each path component, tolerating ones that exist.
bool make_dirs(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); i++) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty() && mkdir(cur.c_str(), 0777) != 0
          && errno != EEXIST) {
        fprintf(stderr, "tokpack: mkdir %s: %s\n", cur.c_str(),
                strerror(errno));
        return false;
      }
    }
    if (i < path.size()) cur.push_back(path[i]);
  }
  return true;
}

// A re-pack into a dir that already holds shards could interrupt and
// leave NEW shards under the OLD index.json — sizes can line up, and
// the reader would silently serve a splice of two corpora.  Refuse
// loudly instead (the Python writer's name_offset is the append path).
bool check_dir_empty_of_shards(const std::string& dir) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return true;  // fresh dir about to be created
  bool clean = true;
  while (dirent* e = readdir(d)) {
    std::string name = e->d_name;
    if (name.size() > 7
        && name.compare(name.size() - 7, 7, ".tokens") == 0) {
      fprintf(stderr, "tokpack: %s already holds %s — refusing to mix "
              "corpora (pack into a fresh dir)\n", dir.c_str(),
              name.c_str());
      clean = false;
      break;
    }
  }
  closedir(d);
  return clean;
}

}  // namespace

int main(int argc, char** argv) {
  std::string out_dir;
  uint64_t shard_tokens = 1 << 24;  // 64 MiB shards
  std::vector<std::string> inputs;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--out" && i + 1 < argc) {
      out_dir = argv[++i];
    } else if (a == "--shard-tokens" && i + 1 < argc) {
      shard_tokens = strtoull(argv[++i], nullptr, 10);
    } else if (a == "--help") {
      fprintf(stderr, "usage: tokpack --out DIR [--shard-tokens N] "
              "FILE... (- for stdin)\n");
      return 1;
    } else {
      inputs.push_back(a);
    }
  }
  if (out_dir.empty() || inputs.empty() || shard_tokens == 0) {
    fprintf(stderr, "tokpack: need --out DIR, >=1 input, and "
            "--shard-tokens >= 1 (--help for usage)\n");
    return 1;
  }
  if (!check_dir_empty_of_shards(out_dir)) return 2;
  if (!make_dirs(out_dir)) return 2;

  ShardWriter writer(out_dir, shard_tokens);
  for (const std::string& path : inputs) {
    FILE* in = path == "-" ? stdin : fopen(path.c_str(), "rb");
    if (in == nullptr) {
      fprintf(stderr, "tokpack: %s: %s\n", path.c_str(), strerror(errno));
      return 2;
    }
    bool ok = pack_stream(in, path.c_str(), &writer);
    if (in != stdin) fclose(in);
    if (!ok) return 2;
  }
  if (!writer.close_shard()) return 2;
  if (writer.counts.empty()) {
    fprintf(stderr, "tokpack: inputs held 0 tokens\n");
    return 2;
  }
  if (!writer.write_index()) return 2;
  uint64_t total = 0;
  for (uint64_t c : writer.counts) total += c;
  fprintf(stderr, "tokpack: %zu shard(s), %" PRIu64 " tokens -> %s\n",
          writer.counts.size(), total, out_dir.c_str());
  return 0;
}
