#!/bin/bash
# TPU node provisioning: install the TPU kernel driver (gasket/accel) and
# the libtpu userland on an Ubuntu host, delivering the node contract the
# device plugin waits on (/dev/accel*, /home/kubernetes/bin/tpu).
#
# TPU-native analog of the reference's NVIDIA ubuntu installer
# (ref: nvidia-driver-installer/ubuntu/entrypoint.sh:33-180): same
# cache-by-version skip, same host-dir delivery + ld.so.conf update, but
# the payload is libtpu + the accel char-device driver instead of a
# vendor .run installer, so no overlayfs redirection is needed — libtpu
# is a single userland .so with a stable install path.

set -o errexit
set -o pipefail
set -u

set -x
TPU_DRIVER_VERSION="${TPU_DRIVER_VERSION:-1.0.0}"
LIBTPU_VERSION="${LIBTPU_VERSION:-0.0.11}"
LIBTPU_DOWNLOAD_URL_DEFAULT="https://storage.googleapis.com/libtpu-releases/libtpu-${LIBTPU_VERSION}.so"
LIBTPU_DOWNLOAD_URL="${LIBTPU_DOWNLOAD_URL:-$LIBTPU_DOWNLOAD_URL_DEFAULT}"
TPU_INSTALL_DIR_HOST="${TPU_INSTALL_DIR_HOST:-/home/kubernetes/bin/tpu}"
TPU_INSTALL_DIR_CONTAINER="${TPU_INSTALL_DIR_CONTAINER:-/usr/local/tpu}"
ROOT_MOUNT_DIR="${ROOT_MOUNT_DIR:-/root}"
CACHE_FILE="${TPU_INSTALL_DIR_CONTAINER}/.cache"
KERNEL_VERSION="$(uname -r)"
set +x

check_cached_version() {
  echo "Checking cached TPU install"
  if [[ ! -f "${CACHE_FILE}" ]]; then
    echo "Cache file ${CACHE_FILE} not found."
    return 1
  fi
  # shellcheck disable=SC1090
  . "${CACHE_FILE}"
  if [[ "${KERNEL_VERSION}" == "${CACHE_KERNEL_VERSION:-}" ]] \
      && [[ "${TPU_DRIVER_VERSION}" == "${CACHE_TPU_DRIVER_VERSION:-}" ]] \
      && [[ "${LIBTPU_VERSION}" == "${CACHE_LIBTPU_VERSION:-}" ]]; then
    echo "Found existing install for kernel ${KERNEL_VERSION}," \
         "driver ${TPU_DRIVER_VERSION}, libtpu ${LIBTPU_VERSION}."
    return 0
  fi
  echo "Cache file ${CACHE_FILE} found but versions didn't match."
  return 1
}

update_cached_version() {
  cat >"${CACHE_FILE}"<<__EOF__
CACHE_KERNEL_VERSION=${KERNEL_VERSION}
CACHE_TPU_DRIVER_VERSION=${TPU_DRIVER_VERSION}
CACHE_LIBTPU_VERSION=${LIBTPU_VERSION}
__EOF__
  echo "Updated cache:"
  cat "${CACHE_FILE}"
}

configure_install_dirs() {
  echo "Configuring installation directories..."
  mkdir -p "${TPU_INSTALL_DIR_CONTAINER}/lib64" \
           "${TPU_INSTALL_DIR_CONTAINER}/bin"
}

install_kernel_driver() {
  # TPU VM images ship the accel driver in-tree; on stock Ubuntu the
  # gasket-dkms package provides it.  Either way the contract is the
  # module being loaded and /dev/accel* appearing.
  if lsmod | grep -qE '^(gasket|accel|tpu_common)'; then
    echo "TPU kernel driver already loaded; skipping module install."
    return 0
  fi
  echo "Installing TPU kernel driver..."
  apt-get update
  apt-get install -y "linux-headers-${KERNEL_VERSION}" gasket-dkms || {
    echo "gasket-dkms unavailable; attempting modprobe of in-tree driver"
  }
  modprobe gasket 2>/dev/null || true
  modprobe accel 2>/dev/null || true
  echo "Installing TPU kernel driver... DONE."
}

download_libtpu() {
  echo "Downloading libtpu ${LIBTPU_VERSION}..."
  curl -L -S -f "${LIBTPU_DOWNLOAD_URL}" \
      -o "${TPU_INSTALL_DIR_CONTAINER}/lib64/libtpu.so"
  chmod 755 "${TPU_INSTALL_DIR_CONTAINER}/lib64/libtpu.so"
  echo "Downloading libtpu... DONE."
}

update_host_ld_cache() {
  echo "Updating host's ld cache..."
  echo "${TPU_INSTALL_DIR_HOST}/lib64" \
      >> "${ROOT_MOUNT_DIR}/etc/ld.so.conf.d/tpu.conf"
  ldconfig -r "${ROOT_MOUNT_DIR}"
  echo "Updating host's ld cache... DONE."
}

prepare_event_dir() {
  # Health-event queue consumed by the device plugin's health checker
  # (tpulib sysfs contract: /var/run/tpu/events).
  mkdir -p "${ROOT_MOUNT_DIR}/var/run/tpu/events"
}

verify_installation() {
  echo "Verifying TPU installation..."
  local chips
  chips="$(ls /dev/accel* 2>/dev/null | wc -l)"
  if [[ "${chips}" -eq 0 ]]; then
    echo "Verification failed: no /dev/accel* device nodes present." >&2
    exit 1
  fi
  if [[ ! -s "${TPU_INSTALL_DIR_CONTAINER}/lib64/libtpu.so" ]]; then
    echo "Verification failed: libtpu.so missing or empty." >&2
    exit 1
  fi
  echo "Verified ${chips} TPU chip device node(s)."
}

main() {
  if check_cached_version && lsmod | grep -qE '^(gasket|accel|tpu_common)'; then
    echo "TPU already installed; nothing to do."
    exit 0
  fi
  configure_install_dirs
  install_kernel_driver
  download_libtpu
  update_host_ld_cache
  prepare_event_dir
  verify_installation
  update_cached_version
}

main "$@"
