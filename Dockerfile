# Release image for the TPU accelerator stack (ref shape: Dockerfile —
# builder stage + minimal runtime).  One image serves every component:
# device plugin, partitioner, scheduler daemons, NRI injector, demos —
# each selected by command in its manifest.
FROM python:3.12-slim-bookworm AS builder

RUN apt-get update && \
    apt-get install -y --no-install-recommends g++ make && \
    rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY Makefile ./
COPY native/ native/
RUN make native

FROM python:3.12-slim-bookworm

WORKDIR /app
# Bake the node daemons' wheels at build time: startup must not depend
# on a package index any more than the reference's static binary does.
COPY requirements-node.txt ./
RUN pip install --no-cache-dir -r requirements-node.txt

COPY container_engine_accelerators_tpu/ container_engine_accelerators_tpu/
COPY cmd/ cmd/
COPY demo/ demo/
COPY example/ example/
COPY --from=builder /src/native/tpushim/build/libtpushim.so \
    /usr/local/lib/libtpushim.so
COPY --from=builder /src/native/dcnxferd/build/dcnxferd \
    /usr/local/bin/dcnxferd
COPY --from=builder /src/native/dcnfastsock/build/libdcnfastsock.so \
    /usr/local/lib/libdcnfastsock.so
# The data-pipeline Job's init container invokes the packer at its
# in-tree path (demo/tpu-training/lm-data-tpu.yaml).
COPY --from=builder /src/native/tokpack/build/tokpack \
    /app/native/tokpack/build/tokpack

ENV PYTHONPATH=/app
CMD ["python3", "/app/cmd/tpu_device_plugin.py"]
# To expose container-level TPU metrics + health monitoring, use:
# CMD ["python3", "/app/cmd/tpu_device_plugin.py", \
#      "--enable-container-tpu-metrics", "--enable-health-monitoring"]
